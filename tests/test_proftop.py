"""Per-op device-time attribution (ISSUE 6): xplane scope aggregation,
FLAGS_op_profile trace identity, the proftop CLI, the debugz
introspection server, and the metrics push exporter.

Layers under test:
  ops/registry.emit_ops + Executor      named-scope tagging (flag-gated,
                                        compile-cache keyed)
  fluid/profiler.xplane_op_events       op-event aggregation incl. the
                                        nested-event (while body) filter
  telemetry/cost.py                     HLO metadata parse, fused split,
                                        neighborhood propagation, report
  tools/proftop.py                      CLI end to end on resnet18
  telemetry/debugz.py                   /metrics /statusz /steps /healthz
  telemetry/export.py                   bounded retry, flag-off, formats
"""
import importlib.util
import json
import os
import sys
import threading
import urllib.request

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers, monitor
from paddle_tpu.telemetry import cost, debugz, export, get_registry, sink


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _make_xspace(events, line_name="tf_XLACpuClient/1", plane_name="/host:CPU"):
    """Synthetic XSpace: events = [(name, offset_ps, dur_ps, is_op)];
    is_op attaches the hlo_op stat the aggregator keys on."""
    xplane_pb2 = pytest.importorskip(
        "tensorflow.tsl.profiler.protobuf.xplane_pb2")
    xs = xplane_pb2.XSpace()
    plane = xs.planes.add(name=plane_name)
    plane.stat_metadata[1].id = 1
    plane.stat_metadata[1].name = "hlo_op"
    line = plane.lines.add(name=line_name, timestamp_ns=1000)
    for i, (name, offset_ps, dur_ps, is_op) in enumerate(events, start=1):
        plane.event_metadata[i].id = i
        plane.event_metadata[i].name = name
        ev = line.events.add(metadata_id=i, offset_ps=offset_ps,
                             duration_ps=dur_ps)
        if is_op:
            st = ev.stats.add(metadata_id=1)
            st.ref_value = i
    return xs


SYNTH_HLO = """\
HloModule jit_fn, entry_computation_layout={()->()}

%fused_computation (p0: f32[4]) -> f32[4] {
  %p0 = f32[4]{0} parameter(0)
  %exp.1 = f32[4]{0} exponential(f32[4]{0} %p0), metadata={op_name="jit(fn)/jit(main)/op3:relu/exp"}
  ROOT %add.2 = f32[4]{0} add(f32[4]{0} %exp.1, f32[4]{0} %p0), metadata={op_name="jit(fn)/jit(main)/op4:scale/add"}
}

ENTRY %main.9 (a: f32[4]) -> f32[4] {
  %a = f32[4]{0} parameter(0)
  %dot.5 = f32[4]{0} dot(f32[4]{0} %a, f32[4]{0} %a), metadata={op_name="jit(fn)/jit(main)/op0:matmul/dot_general"}
  %copy.7 = f32[4]{0} copy(f32[4]{0} %dot.5)
  %while.8 = f32[4]{0} while(f32[4]{0} %copy.7), metadata={op_name="jit(fn)/jit(main)/fwk:rng_advance/while"}
  ROOT %my_fusion = f32[4]{0} fusion(f32[4]{0} %while.8), kind=kLoop, calls=%fused_computation
}
"""


def _tiny_train_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", [8, 16], append_batch_size=False)
        y = layers.data("y", [8, 1], append_batch_size=False)
        loss = layers.mean(
            layers.square_error_cost(layers.fc(x, 4), y))
        fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
    rng = np.random.RandomState(0)
    feed = {"x": rng.rand(8, 16).astype(np.float32),
            "y": rng.rand(8, 1).astype(np.float32)}
    return main, startup, feed, loss


def _load_tool(name):
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", f"{name}.py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _op_profile_off():
    """Every test starts and ends with the flag off (the default)."""
    yield
    fluid.flags.set_flags({"FLAGS_op_profile": False})


# ---------------------------------------------------------------------------
# xplane aggregation
# ---------------------------------------------------------------------------


def test_xplane_aggregation_sums_and_filters():
    from paddle_tpu.fluid import profiler

    xs = _make_xspace([
        ("dot.5", 0, 600_000, True),
        ("dot.5", 1_000_000, 400_000, True),       # second step, same op
        ("ThunkExecutor::Execute", 0, 2_000_000, False),  # host span: out
        ("my_fusion", 2_000_000, 300_000, True),
    ])
    out = profiler.xplane_op_events(xs)
    assert set(out) == {"dot.5", "my_fusion"}
    assert out["dot.5"]["dur_ps"] == 1_000_000
    assert out["dot.5"]["count"] == 2
    assert out["my_fusion"]["dur_ps"] == 300_000


def test_xplane_nested_op_events_charge_the_outer_span():
    """A while instruction's span contains its body's op events — the
    body must not double-count (the scanned-encoder case)."""
    from paddle_tpu.fluid import profiler

    xs = _make_xspace([
        ("while.8", 0, 1_000_000, True),
        ("dot.inner", 100_000, 200_000, True),     # inside while.8
        ("add.inner", 400_000, 100_000, True),     # inside while.8
        ("dot.outer", 2_000_000, 500_000, True),   # disjoint
    ])
    out = profiler.xplane_op_events(xs)
    assert "dot.inner" not in out and "add.inner" not in out
    assert out["while.8"]["dur_ps"] == 1_000_000
    assert out["dot.outer"]["dur_ps"] == 500_000


# ---------------------------------------------------------------------------
# HLO metadata parse + cost report join
# ---------------------------------------------------------------------------


def test_parse_hlo_scopes_fusion_and_propagation():
    instrs = cost.parse_hlo_metadata(SYNTH_HLO)
    assert instrs["dot.5"]["scopes"] == [("op", 0, "matmul")]
    # fusion splits across its body's scopes
    assert sorted(instrs["my_fusion"]["scopes"]) == [
        ("op", 3, "relu"), ("op", 4, "scale")]
    # metadata-less copy.7 propagates from its operand (dot.5)
    assert instrs["copy.7"]["scopes"] == [("op", 0, "matmul")]
    # framework scope recognized
    assert instrs["while.8"]["scopes"] == [("fwk", "rng_advance")]


def test_cost_report_fused_split_and_coverage():
    events = {
        "dot.5": {"dur_ps": 600_000_000, "count": 3},
        "my_fusion": {"dur_ps": 400_000_000, "count": 3},  # ops 3+4 fused
        "while.8": {"dur_ps": 100_000_000, "count": 3},    # fwk
        "unknown.1": {"dur_ps": 50_000_000, "count": 3},   # unattributed
    }
    rep = cost.build_cost_report(events, SYNTH_HLO, steps=3,
                                 peak_flops=1e12)
    by_scope = {r.scope: r for r in rep.rows}
    assert by_scope["op0:matmul"].device_ms == pytest.approx(0.6)
    assert not by_scope["op0:matmul"].fused
    # 0.4ms fusion split pro-rata across op3/op4
    assert by_scope["op3:relu"].device_ms == pytest.approx(0.2)
    assert by_scope["op4:scale"].device_ms == pytest.approx(0.2)
    assert by_scope["op3:relu"].fused and by_scope["op4:scale"].fused
    assert rep.framework["rng_advance"] == pytest.approx(0.1)
    # coverage counts op + framework scopes; unknown.1 dilutes it
    assert rep.coverage == pytest.approx(1.1 / 1.15)
    assert rep.unattributed["unknown.1"] == pytest.approx(0.05)
    assert rep.device_ms_per_step == pytest.approx(1.1 / 3)
    # the report landed on the debugz hook and in the registry
    assert cost.last_report() is rep
    assert get_registry().gauge("op_profile_coverage").value == pytest.approx(
        rep.coverage)


def test_cost_report_joins_program_callstacks():
    main, startup, feed, loss = _tiny_train_program()
    ops = main.global_block().ops
    idx = next(i for i, op in enumerate(ops) if op.type == "mul")
    hlo = (f'ENTRY %main.1 (a: f32[4]) -> f32[4] {{\n'
           f'  ROOT %dot.1 = f32[4]{{0}} dot(), '
           f'metadata={{op_name="jit(fn)/op{idx}:mul/dot_general"}}\n'
           f'}}\n')
    rep = cost.build_cost_report(
        {"dot.1": {"dur_ps": 1_000_000, "count": 1}}, hlo, program=main)
    (row,) = rep.rows
    assert row.op_index == idx and row.op_type == "mul"
    # the layer names THIS test file (the user's layer call)
    assert row.layer and "test_proftop.py" in row.layer
    assert rep.by_layer  # rollup keyed by the same frame


# ---------------------------------------------------------------------------
# FLAGS_op_profile: trace identity + cache behavior
# ---------------------------------------------------------------------------


def test_op_profile_off_trace_identical_and_cache_stable():
    main, startup, feed, loss = _tiny_train_program()
    exe = fluid.Executor()
    exe.run(startup)
    baseline = exe.aot_step(main, feed=feed, fetch_list=[loss]).as_text()
    assert "op0:" not in baseline and "fwk:" not in baseline
    n_cache = len(exe._cache)

    fluid.flags.set_flags({"FLAGS_op_profile": True})
    tagged = exe.aot_step(main, feed=feed, fetch_list=[loss]).as_text()
    assert len(exe._cache) == n_cache + 1  # flag is in the cache key
    assert "0:" in tagged and "fwk:rng_advance" in tagged
    assert any(f"op_name=\"jit" in ln and ":mul" in ln
               for ln in tagged.splitlines())

    # toggling back off hits the ORIGINAL entry and the ORIGINAL trace
    fluid.flags.set_flags({"FLAGS_op_profile": False})
    again = exe.aot_step(main, feed=feed, fetch_list=[loss]).as_text()
    assert len(exe._cache) == n_cache + 1
    assert again == baseline


def test_op_profile_on_same_numerics():
    from paddle_tpu.fluid.executor import Scope

    def run(profile):
        fluid.flags.set_flags({"FLAGS_op_profile": profile})
        main, startup, feed, loss = _tiny_train_program()
        exe = fluid.Executor()
        scope = Scope()  # isolated: identical seed -> identical init
        exe.run(startup, scope=scope)
        (v,) = exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
        return np.asarray(v)

    np.testing.assert_allclose(run(False), run(True), rtol=0, atol=0)


# ---------------------------------------------------------------------------
# proftop CLI (in-process, resnet18 tiny shapes)
# ---------------------------------------------------------------------------


@pytest.mark.slow  # ~21s: the single heaviest tier-1 test, and ci.sh's
# proftop smoke already asserts the same coverage/callstack/MFU bars on
# resnet50 AND bert through this CLI — wall-time triage (870s gate)
def test_proftop_cli_resnet18(capsys):
    proftop = _load_tool("proftop")
    rc = proftop.main(["--model", "resnet18", "--steps", "2",
                       "--image-size", "32", "--json"])
    assert rc == 0
    out = capsys.readouterr().out
    line = [ln for ln in out.splitlines() if ln.startswith("{")][-1]
    rep = json.loads(line)
    assert rep["model"] == "resnet18"
    # the acceptance bar: >=90% of op time lands on named scopes
    assert rep["coverage"] >= 0.9, rep["coverage"]
    assert rep["rows"], "no attributed op rows"
    for row in rep["rows"]:
        assert row["op_index"] >= 0
        assert row["layer"], f"row {row['scope']} lost its callstack"
    # measured-MFU gauge vs bench.py's model formula: same time base, so
    # the ratio compares flop accounting — documented tolerance 2x
    assert rep["measured_mfu"] is not None and rep["formula_mfu"] is not None
    ratio = rep["measured_mfu"] / rep["formula_mfu"]
    assert 0.5 <= ratio <= 2.0, ratio
    assert get_registry().gauge("measured_mfu").value == rep["measured_mfu"]


def test_proftop_trace_dir_mode(tmp_path, capsys):
    """--trace_dir aggregates an existing dump; with --hlo it joins
    scopes (no model build, no jax profiling)."""
    proftop = _load_tool("proftop")
    xs = _make_xspace([("dot.5", 0, 600_000, True),
                       ("my_fusion", 1_000_000, 400_000, True)])
    d = tmp_path / "plugins" / "profile" / "run1"
    d.mkdir(parents=True)
    (d / "host.xplane.pb").write_bytes(xs.SerializeToString())
    hlo = tmp_path / "step.hlo.txt"
    hlo.write_text(SYNTH_HLO)
    rc = proftop.main(["--trace_dir", str(tmp_path), "--hlo", str(hlo),
                       "--json"])
    assert rc == 0
    rep = json.loads(capsys.readouterr().out.splitlines()[-1])
    scopes = {r["scope"] for r in rep["rows"]}
    assert {"op0:matmul", "op3:relu", "op4:scale"} <= scopes


# ---------------------------------------------------------------------------
# debugz introspection server
# ---------------------------------------------------------------------------


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5) as r:
        return r.status, r.read().decode()


def test_debugz_endpoints():
    debugz.stop()
    cost._last_report = None
    get_registry().counter("debugz_test_total", "t").inc(3)
    srv = debugz.serve(port=0)
    try:
        port = srv.server_address[1]
        status, body = _get(port, "/healthz")
        assert status == 200 and body.strip() == "ok"

        # /metrics: valid Prometheus exposition (TYPE headers + samples)
        status, body = _get(port, "/metrics")
        assert status == 200
        assert "# TYPE debugz_test_total counter" in body
        assert any(ln.split() == ["debugz_test_total", "3"]
                   for ln in body.splitlines())

        status, body = _get(port, "/statusz")
        st = json.loads(body)
        assert {"build", "flags", "mesh", "steps", "pid"} <= set(st)
        assert "FLAGS_op_profile" in st["flags"]

        status, body = _get(port, "/steps")
        assert status == 200 and isinstance(json.loads(body), list)

        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(port, "/proftop")  # no report built yet
        assert ei.value.code == 404
        cost.build_cost_report(
            {"dot.5": {"dur_ps": 1_000_000, "count": 1}}, SYNTH_HLO)
        status, body = _get(port, "/proftop")
        assert status == 200 and "coverage" in json.loads(body)
    finally:
        debugz.stop()


def test_debugz_armed_by_step_loop(monkeypatch):
    """PADDLE_DEBUGZ_PORT arms the server from the executor step loop
    (launch.py sets the var per rank) and /steps serves breakdowns even
    with the JSONL sink off."""
    debugz.stop()
    monitor.reset_for_tests()
    monkeypatch.setenv("PADDLE_DEBUGZ_PORT", "0")  # ephemeral
    try:
        main, startup, feed, loss = _tiny_train_program()
        exe = fluid.Executor()
        exe.run(startup)
        for _ in range(3):
            exe.run(main, feed=feed, fetch_list=[loss])
        assert debugz.armed()
        port = debugz._server.server_address[1]
        status, body = _get(port, "/steps")
        steps = json.loads(body)
        assert steps, "step records missing with debugz armed"
        assert {"step", "device_ms", "compile_ms",
                "cache_hit"} <= set(steps[-1])
    finally:
        debugz.stop()
        monitor.reset_for_tests()


# ---------------------------------------------------------------------------
# push exporter
# ---------------------------------------------------------------------------


class _Collector:
    """Tiny local collector: records POSTs, optionally failing the
    first N with HTTP 500."""

    def __init__(self, fail_first=0):
        from http.server import BaseHTTPRequestHandler, HTTPServer

        self.bodies = []
        self.headers = []
        self.attempts = 0
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):
                outer.attempts += 1
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n)
                if outer.attempts <= fail_first:
                    self.send_response(500)
                    self.end_headers()
                    return
                outer.bodies.append(body)
                outer.headers.append(dict(self.headers))
                self.send_response(200)
                self.end_headers()

            def log_message(self, fmt, *args):
                pass

        self.server = HTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.server.server_address[1]
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()

    def url(self, path="/ingest"):
        return f"http://127.0.0.1:{self.port}{path}"

    def close(self):
        self.server.shutdown()
        self.server.server_close()


def test_exporter_flag_off_means_no_exporter(monkeypatch):
    export.stop()
    monkeypatch.delenv(export.ENV_URL, raising=False)
    assert export.maybe_start() is None
    assert export.active() is None
    export.stop()


def test_exporter_pushes_otlp_shaped_snapshot():
    export.stop()
    col = _Collector()
    try:
        get_registry().counter("export_test_total", "t").inc(7)
        exp = export.PushExporter(col.url(), interval_s=60, retries=2)
        assert exp.flush()
        payload = json.loads(col.bodies[-1])
        assert payload["resource"]["pid"] == os.getpid()
        series = payload["metrics"]["export_test_total"]["series"]
        assert series[0]["value"] == 7
        assert get_registry().counter("metrics_push_total").value >= 1
    finally:
        col.close()
        export.stop()


def test_exporter_retry_is_bounded_with_backoff():
    export.stop()
    fails = get_registry().counter("metrics_push_failures_total").value
    col = _Collector(fail_first=100)  # always failing
    try:
        exp = export.PushExporter(col.url(), interval_s=60, retries=3,
                                  backoff_s=0.01)
        assert not exp.flush()
        assert col.attempts == 3  # bounded: exactly `retries` attempts
        assert (get_registry().counter("metrics_push_failures_total").value
                == fails + 1)
        # recovery: collector comes back, next interval delivers
        col2 = _Collector()
        exp.url = col2.url()
        assert exp.flush()
        col2.close()
    finally:
        col.close()
        export.stop()


def test_exporter_retries_then_succeeds():
    export.stop()
    col = _Collector(fail_first=2)
    try:
        exp = export.PushExporter(col.url(), interval_s=60, retries=3,
                                  backoff_s=0.01)
        assert exp.flush()
        assert col.attempts == 3 and len(col.bodies) == 1
    finally:
        col.close()
        export.stop()


def test_exporter_pushgateway_format_is_prometheus_text():
    export.stop()
    col = _Collector()
    try:
        get_registry().counter("export_pg_total", "t").inc()
        exp = export.PushExporter(col.url("/metrics/job/paddle"),
                                  interval_s=60)
        assert exp.fmt == "prom"
        assert exp.flush()
        assert b"# TYPE export_pg_total counter" in col.bodies[-1]
        assert "text/plain" in col.headers[-1].get("Content-Type", "")
    finally:
        col.close()
        export.stop()


def test_exporter_env_arming(monkeypatch):
    export.stop()
    col = _Collector()
    try:
        monkeypatch.setenv(export.ENV_URL, col.url())
        monkeypatch.setenv(export.ENV_SECS, "60")
        exp = export.maybe_start()
        assert exp is not None and exp.flush()
    finally:
        col.close()
        export.stop()


# ---------------------------------------------------------------------------
# satellite: registry exposition fixes + sink pid fallback
# ---------------------------------------------------------------------------


def test_prometheus_label_value_escaping():
    from paddle_tpu.telemetry.registry import MetricsRegistry

    reg = MetricsRegistry()
    reg.counter("esc_total", "t", path='C:\\tmp\n"x"').inc()
    text = reg.to_prometheus()
    line = [ln for ln in text.splitlines()
            if ln.startswith("esc_total{")][0]
    assert '\\\\tmp' in line and '\\"x\\"' in line and '\\n' in line
    assert "\n" not in line  # the raw newline must not tear the sample


def test_empty_histogram_is_well_defined():
    from paddle_tpu.telemetry.registry import Histogram

    h = Histogram()
    s = h.summary()
    assert s == {"count": 0, "sum": 0.0, "min": 0.0, "max": 0.0,
                 "avg": 0.0}
    assert h.quantile(0.5) == 0.0
    assert h.quantile(0.99) == 0.0


def test_sink_placeholder_falls_back_to_pid(monkeypatch):
    from paddle_tpu.telemetry.sink import _expand

    monkeypatch.delenv("PADDLE_TRAINER_ID", raising=False)
    # un-launched processes sharing a template must not collide
    assert _expand("/tmp/m.{rank}.jsonl", 0) == \
        f"/tmp/m.pid{os.getpid()}.jsonl"
    assert _expand("/tmp/m.%r.jsonl", 0) == \
        f"/tmp/m.pid{os.getpid()}.jsonl"
    # explicit placeholder-free paths stay exactly as given (CI contract)
    assert _expand("/tmp/m.jsonl", 0) == "/tmp/m.jsonl"
    # launched processes keep the rank expansion
    monkeypatch.setenv("PADDLE_TRAINER_ID", "2")
    assert _expand("/tmp/m.{rank}.jsonl", 2) == "/tmp/m.2.jsonl"
    assert _expand("/tmp/m.jsonl", 2) == "/tmp/m.rank2.jsonl"
