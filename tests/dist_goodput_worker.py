"""Worker for tests/test_goodput.py kill-one-of-two drill: a 2-rank
launcher job under --fleetz_port where one tag dies once mid-run; the
survivors' goodput ledgers + the launcher lifecycle ledger must let
goodtop classify EVERY wall-clock second, with the restart window
attributed `restart_recovery` and decomposed detection/respawn/
recompile/replay.

Each rank trains an independent tiny least-squares program (no
collectives — a dead peer must not wedge the survivor; the launcher's
group restart is the coupling) with real Executor steps, a real
CheckpointManager (restore on respawn => replay accounting), and lease
renewals carrying the fleet payloads (launch --fleetz_port arms
PADDLE_GOODPUT / PADDLE_FLEET_METRICS + the heartbeat/lease channel).

Env knobs:
  GOODPUT_TEST_DIR       checkpoint root (per-tag subdirs)
  GOODPUT_TEST_DIE_TAG   stable tag that dies once (incarnation 0 only)
  GOODPUT_TEST_DIE_AT    ...right after this many local steps
  GOODPUT_TEST_STEPS     total steps (default 10)
  GOODPUT_TEST_CKPT_FREQ checkpoint every N steps (default 2)
  GOODPUT_TEST_FLEETZ    launcher fleetz port: rank 0 scrapes
                         /fleetz + /fleetz/metrics near the end of the
                         run and saves them beside the checkpoints
  GOODPUT_TEST_STEP_SLEEP per-step compute pad seconds (default 0.04)
"""
import json
import os
import sys
import time
import urllib.request

import numpy as np

from paddle_tpu import fluid
from paddle_tpu.distributed.heartbeat import start_heartbeat
from paddle_tpu.fluid import checkpoint as ckpt_mod
from paddle_tpu.fluid import layers


def main() -> int:
    rank = int(os.environ.get("PADDLE_TRAINER_ID", 0))
    tag = os.environ.get("PADDLE_TRAINER_TAG", f"trainer{rank}")
    gen = int(os.environ.get("PADDLE_ELASTIC_RESTART", 0))
    root = os.environ["GOODPUT_TEST_DIR"]
    die_tag = os.environ.get("GOODPUT_TEST_DIE_TAG", "")
    die_at = int(os.environ.get("GOODPUT_TEST_DIE_AT", 0))
    steps = int(os.environ.get("GOODPUT_TEST_STEPS", 10))
    freq = int(os.environ.get("GOODPUT_TEST_CKPT_FREQ", 2))
    fleetz = os.environ.get("GOODPUT_TEST_FLEETZ")
    pad = float(os.environ.get("GOODPUT_TEST_STEP_SLEEP", 0.04))

    hb = start_heartbeat(interval=0.25)  # renewals carry fleet payloads

    batch = 8
    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        x = layers.data("x", [batch, 4], append_batch_size=False)
        y = layers.data("y", [batch, 1], append_batch_size=False)
        loss = layers.mean(layers.square_error_cost(layers.fc(x, 1), y))
        fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)

    exe = fluid.Executor()
    scope = fluid.executor.Scope()
    rng = np.random.RandomState(7 + rank)
    xa = rng.rand(batch, 4).astype(np.float32)
    ya = xa.sum(1, keepdims=True).astype(np.float32)

    with fluid.scope_guard(scope):
        exe.run(startup)
        mgr = ckpt_mod.CheckpointManager(
            os.path.join(root, tag), program=main_prog, scope=scope)
        start = 0
        if gen > 0:
            out = mgr.restore(program=main_prog, scope=scope)
            if out is not None:
                start = int((out.get("extra") or {}).get("next_step", 0))
        for step in range(start, steps):
            exe.run(main_prog, feed={"x": xa, "y": ya},
                    fetch_list=[loss])
            time.sleep(pad)  # visible productive/idle structure
            if (step + 1) % freq == 0:
                mgr.save(step, extra_state={"next_step": step + 1})
            if tag == die_tag and gen == 0 and step + 1 == die_at:
                os._exit(17)  # hard death mid-job (no atexit, no drain)
        if rank == 0 and fleetz:
            _scrape_fleet(fleetz, root)
        # one more renewal window so the coordinator holds the final
        # ledger summary before the launcher tears everything down
        time.sleep(0.6)
    if hb is not None:
        hb.stop()
    return 0


def _scrape_fleet(port: str, root: str) -> None:
    """GET the launcher's /fleetz + /fleetz/metrics while the fleet is
    alive; the test asserts on the saved copies after the job."""
    for attempt in range(10):
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/fleetz", timeout=2) as r:
                fleet = json.loads(r.read().decode())
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/fleetz/metrics",
                    timeout=2) as r:
                text = r.read().decode()
            if len(fleet.get("ranks") or {}) >= 2:
                with open(os.path.join(root, "fleetz.json"), "w") as f:
                    json.dump(fleet, f)
                with open(os.path.join(root, "fleetz_metrics.txt"),
                          "w") as f:
                    f.write(text)
                return
        except Exception:  # noqa: BLE001 — retry until renewals landed
            pass
        time.sleep(0.5)


if __name__ == "__main__":
    sys.exit(main())
