"""Sequence-model integration tests (the reference's tests/book pattern:
build a classic model, train a few steps on fixed data, assert the loss
drops — SURVEY.md §4.2).

Models:
  - seq2seq encoder-decoder (book/test_rnn_encoder_decoder.py shape):
    dynamic_lstm encoder -> dynamic_lstm decoder, toy copy task
  - SRL-style CRF tagger (book/test_label_semantic_roles.py shape):
    embedding + bi-LSTM + linear_chain_crf + crf_decoding
  - sentiment conv (book/test_understand_sentiment.py conv variant):
    embedding + sequence_conv + sequence_pool
  - Transformer NMT encoder-decoder program builds and runs one step
    (dist_transformer.py capability check: causal self-attention +
    cross-attention via fused_multihead_attention)
"""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers


def _train(main, startup, feeds, fetch, steps=25):
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.executor.Scope()):
        exe.run(startup)
        losses = []
        for _ in range(steps):
            (lv,) = exe.run(main, feed=feeds, fetch_list=[fetch])
            losses.append(float(np.asarray(lv).reshape(())))
    assert all(np.isfinite(l) for l in losses), losses
    assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])
    return losses


def test_seq2seq_encoder_decoder_trains():
    """Copy task: decoder reproduces the (reversed) source sequence."""
    rng = np.random.RandomState(0)
    B, T, V, H = 8, 6, 20, 32
    src = rng.randint(1, V, (B, T)).astype(np.int64)
    tgt_in = np.concatenate([np.zeros((B, 1), np.int64), src[:, :-1]], axis=1)
    lens = np.full((B,), T, np.int32)

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        s = layers.data("src", [B, T], dtype="int64", append_batch_size=False)
        ti = layers.data("tgt_in", [B, T], dtype="int64", append_batch_size=False)
        tl = layers.data("tgt_lbl", [B, T], dtype="int64", append_batch_size=False)
        ln = layers.data("lens", [B], dtype="int32", append_batch_size=False)

        emb = layers.embedding(s, size=[V, H], param_attr=fluid.ParamAttr(name="src_emb"))
        enc_proj = layers.fc(emb, H * 4, num_flatten_dims=2)
        enc_h, enc_c = layers.dynamic_lstm(enc_proj, H * 4, length=ln)
        enc_last = layers.sequence_last_step(enc_h, length=ln)
        enc_last_c = layers.sequence_last_step(enc_c, length=ln)

        demb = layers.embedding(ti, size=[V, H], param_attr=fluid.ParamAttr(name="tgt_emb"))
        dec_proj = layers.fc(demb, H * 4, num_flatten_dims=2)
        dec_h, _ = layers.dynamic_lstm(
            dec_proj, H * 4, h_0=enc_last, c_0=enc_last_c, length=ln
        )
        logits = layers.fc(dec_h, V, num_flatten_dims=2)
        loss = layers.softmax_with_cross_entropy(
            layers.reshape(logits, [B * T, V]),
            layers.reshape(tl, [B * T, 1]),
        )
        avg = layers.mean(loss)
        fluid.optimizer.AdamOptimizer(learning_rate=3e-3).minimize(avg)

    feeds = {"src": src, "tgt_in": tgt_in, "tgt_lbl": src, "lens": lens}
    _train(main, startup, feeds, avg, steps=30)


def test_crf_tagger_trains_and_decodes():
    rng = np.random.RandomState(1)
    B, T, V, H, NTAG = 6, 5, 30, 24, 4
    words = rng.randint(0, V, (B, T)).astype(np.int64)
    tags = (words % NTAG).astype(np.int64)  # learnable mapping
    lens = rng.randint(3, T + 1, (B,)).astype(np.int32)

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        w = layers.data("words", [B, T], dtype="int64", append_batch_size=False)
        t = layers.data("tags", [B, T], dtype="int64", append_batch_size=False)
        ln = layers.data("lens", [B], dtype="int32", append_batch_size=False)
        emb = layers.embedding(w, size=[V, H])
        fwd_proj = layers.fc(emb, H * 4, num_flatten_dims=2)
        h_f, _ = layers.dynamic_lstm(fwd_proj, H * 4, length=ln)
        bwd_proj = layers.fc(emb, H * 4, num_flatten_dims=2)
        h_b, _ = layers.dynamic_lstm(bwd_proj, H * 4, length=ln, is_reverse=True)
        feat = layers.concat([h_f, h_b], axis=-1)
        emission = layers.fc(feat, NTAG, num_flatten_dims=2)
        nll = layers.linear_chain_crf(
            emission, t, param_attr=fluid.ParamAttr(name="crfw"), length=ln
        )
        avg = layers.mean(nll)
        path = layers.crf_decoding(emission, "crfw", length=ln)
        fluid.optimizer.AdamOptimizer(learning_rate=5e-3).minimize(avg)

    exe = fluid.Executor()
    with fluid.scope_guard(fluid.executor.Scope()):
        exe.run(startup)
        feeds = {"words": words, "tags": tags, "lens": lens}
        losses = []
        for _ in range(40):
            lv, pv = exe.run(main, feed=feeds, fetch_list=[avg, path])
            losses.append(float(np.asarray(lv).reshape(())))
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
        # decode accuracy on valid positions should beat chance by a lot
        pv = np.asarray(pv)
        mask = np.arange(T)[None, :] < lens[:, None]
        acc = (pv == tags)[mask].mean()
        assert acc > 0.6, acc


def test_sentiment_conv_trains():
    rng = np.random.RandomState(2)
    B, T, V, H = 8, 7, 40, 16
    words = rng.randint(0, V, (B, T)).astype(np.int64)
    label = (words.sum(1) % 2).astype(np.int64)[:, None]
    lens = np.full((B,), T, np.int32)

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        w = layers.data("words", [B, T], dtype="int64", append_batch_size=False)
        y = layers.data("label", [B, 1], dtype="int64", append_batch_size=False)
        ln = layers.data("lens", [B], dtype="int32", append_batch_size=False)
        emb = layers.embedding(w, size=[V, H])
        conv = layers.sequence_conv(emb, num_filters=H, filter_size=3,
                                    length=ln, act="tanh")
        pooled = layers.sequence_pool(conv, "MAX", length=ln)
        logits = layers.fc(pooled, 2)
        loss = layers.softmax_with_cross_entropy(logits, y)
        avg = layers.mean(loss)
        fluid.optimizer.AdamOptimizer(learning_rate=5e-3).minimize(avg)

    _train(main, startup, {"words": words, "label": label, "lens": lens}, avg,
           steps=40)


def test_transformer_nmt_program_builds_and_steps():
    """Transformer-base NMT shape (dist_transformer.py capability): causal
    decoder self-attention + encoder-decoder cross attention, one train
    step executes with finite loss."""
    rng = np.random.RandomState(3)
    B, T, V, H, NH = 4, 8, 50, 32, 4

    def mha(q_in, kv_in, causal=False, prefix=""):
        q = layers.fc(q_in, H, num_flatten_dims=2)
        k = layers.fc(kv_in, H, num_flatten_dims=2)
        v = layers.fc(kv_in, H, num_flatten_dims=2)
        helper = fluid.layer_helper.LayerHelper("fused_mha" + prefix)
        out = helper.create_variable_for_type_inference("float32")
        helper.append_op(
            type="fused_multihead_attention",
            inputs={"Q": [q], "K": [k], "V": [v]},
            outputs={"Out": [out]},
            attrs={"num_heads": NH, "causal": causal, "is_test": False,
                   "dropout_prob": 0.0},
        )
        return layers.fc(out, H, num_flatten_dims=2)

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        s = layers.data("src", [B, T], dtype="int64", append_batch_size=False)
        ti = layers.data("tgt_in", [B, T], dtype="int64", append_batch_size=False)
        tl = layers.data("tgt_lbl", [B, T], dtype="int64", append_batch_size=False)

        enc = layers.embedding(s, size=[V, H])
        enc = layers.layer_norm(enc + mha(enc, enc, prefix="e"),
                                begin_norm_axis=2)
        enc = layers.layer_norm(
            enc + layers.fc(layers.fc(enc, H * 2, num_flatten_dims=2, act="relu"),
                            H, num_flatten_dims=2),
            begin_norm_axis=2)

        dec = layers.embedding(ti, size=[V, H])
        dec = layers.layer_norm(dec + mha(dec, dec, causal=True, prefix="d1"),
                                begin_norm_axis=2)
        dec = layers.layer_norm(dec + mha(dec, enc, prefix="d2"),
                                begin_norm_axis=2)
        logits = layers.fc(dec, V, num_flatten_dims=2)
        loss = layers.softmax_with_cross_entropy(
            layers.reshape(logits, [B * T, V]),
            layers.reshape(tl, [B * T, 1]),
        )
        avg = layers.mean(loss)
        fluid.optimizer.AdamOptimizer(learning_rate=1e-3).minimize(avg)

    src = rng.randint(1, V, (B, T)).astype(np.int64)
    tgt_in = np.concatenate([np.zeros((B, 1), np.int64), src[:, :-1]], 1)
    _train(main, startup, {"src": src, "tgt_in": tgt_in, "tgt_lbl": src}, avg,
           steps=30)


def test_beam_search_decode_loop():
    """Stepwise beam decode driving the beam_search op: a toy LM whose
    argmax chain is known; beam width 2 recovers it."""
    V, W, steps = 6, 2, 4
    # transition log-probs: token t -> t+1 is best
    logp = np.full((V, V), -5.0, np.float32)
    for t in range(V - 1):
        logp[t, t + 1] = -0.1
    logp[:, 0] += 1e-3  # tiny tiebreak noise elsewhere

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        pre_ids = layers.data("pre_ids", [W, 1], dtype="int64", append_batch_size=False)
        pre_sc = layers.data("pre_sc", [W, 1], dtype="float32", append_batch_size=False)
        sc = layers.data("sc", [W, V], dtype="float32", append_batch_size=False)
        ids, scs, parent = layers.beam_search(pre_ids, pre_sc, sc, beam_size=W, end_id=V - 1)

    exe = fluid.Executor()
    with fluid.scope_guard(fluid.executor.Scope()):
        exe.run(startup)
        cur = np.asarray([[1], [1]], np.int64)
        cur_sc = np.asarray([[0.0], [-1e9]], np.float32)  # one live beam
        toks = []
        for _ in range(steps):
            step_scores = logp[cur[:, 0]]
            i, s_, p = exe.run(
                main,
                feed={"pre_ids": cur, "pre_sc": cur_sc, "sc": step_scores},
                fetch_list=[ids, scs, parent],
            )
            cur, cur_sc = np.asarray(i), np.asarray(s_)
            toks.append(cur[0, 0])
        assert toks == [2, 3, 4, 5], toks


def test_word2vec_trains():
    """book/test_word2vec.py shape: N-gram context -> next word via
    shared embeddings; loss memorizes a tiny corpus."""
    rng = np.random.RandomState(4)
    V, E, B = 40, 16, 32
    # synthetic corpus with strong 3-gram structure
    corpus = rng.randint(0, V, 300)
    ctxs, tgts = [], []
    for i in range(len(corpus) - 3):
        ctxs.append(corpus[i:i + 3])
        tgts.append(corpus[(i * 7) % V])  # deterministic mapping to learn
    ctx = np.asarray(ctxs[:B * 4], np.int64)
    tgt = np.asarray(tgts[:B * 4], np.int64)[:, None]

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        w = layers.data("ctx", [B, 3], dtype="int64", append_batch_size=False)
        y = layers.data("y", [B, 1], dtype="int64", append_batch_size=False)
        emb = layers.embedding(w, size=[V, E],
                               param_attr=fluid.ParamAttr(name="shared_emb"))
        flat = layers.reshape(emb, [B, 3 * E])
        hidden = layers.fc(flat, 64, act="relu")
        logits = layers.fc(hidden, V)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.AdamOptimizer(learning_rate=3e-3).minimize(loss)

    exe = fluid.Executor()
    with fluid.scope_guard(fluid.executor.Scope()):
        exe.run(startup)
        losses = []
        for epoch in range(25):
            for s in range(0, len(ctx) - B + 1, B):
                (lv,) = exe.run(main, feed={"ctx": ctx[s:s + B], "y": tgt[s:s + B]},
                                fetch_list=[loss])
            losses.append(float(np.asarray(lv).reshape(())))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_recommender_system_trains():
    """book/test_recommender_system.py shape: user+item towers, cosine
    similarity scaled to a rating prediction."""
    rng = np.random.RandomState(5)
    B, NU, NI, E = 32, 50, 60, 16
    users = rng.randint(0, NU, (B * 4,)).astype(np.int64)
    items = rng.randint(0, NI, (B * 4,)).astype(np.int64)
    # learnable synthetic ratings from latent structure
    u_lat = rng.randn(NU, 4); i_lat = rng.randn(NI, 4)
    ratings = np.clip(
        ((u_lat[users] * i_lat[items]).sum(1, keepdims=True) + 2.5), 0, 5
    ).astype(np.float32)

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        u = layers.data("u", [B], dtype="int64", append_batch_size=False)
        it = layers.data("i", [B], dtype="int64", append_batch_size=False)
        r = layers.data("r", [B, 1], append_batch_size=False)
        ue = layers.fc(layers.embedding(u, size=[NU, E]), 32, act="relu")
        ie = layers.fc(layers.embedding(it, size=[NI, E]), 32, act="relu")
        sim = layers.cos_sim(ue, ie)
        pred = layers.scale(sim, scale=5.0)
        loss = layers.mean(layers.square_error_cost(pred, r))
        fluid.optimizer.AdamOptimizer(learning_rate=5e-3).minimize(loss)

    exe = fluid.Executor()
    with fluid.scope_guard(fluid.executor.Scope()):
        exe.run(startup)
        losses = []
        for epoch in range(30):
            for s in range(0, len(users) - B + 1, B):
                (lv,) = exe.run(
                    main,
                    feed={"u": users[s:s + B], "i": items[s:s + B],
                          "r": ratings[s:s + B]},
                    fetch_list=[loss],
                )
            losses.append(float(np.asarray(lv).reshape(())))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


@pytest.mark.slow  # 19s breadth sweep; fused/decoder tests keep tier-1 coverage
def test_transformer_model_family_trains():
    """models/transformer.py (Transformer-base NMT, BASELINE config):
    tiny config trains, causal decoder masks the future."""
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu.models.transformer import (
        TransformerConfig, build_transformer_nmt_program, random_nmt_batch)

    cfg = TransformerConfig.tiny()
    m, st, feeds, loss = build_transformer_nmt_program(cfg, 4, 16, 12)
    with fluid.program_guard(m, st):
        fluid.optimizer.AdamOptimizer(2e-3).minimize(loss)
    scope = fluid.executor.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(st)
        feed = random_nmt_batch(cfg, 4, 16, 12, seed=0)
        vals = []
        for _ in range(20):
            (lv,) = exe.run(m, feed=feed, fetch_list=[loss])
            vals.append(float(np.asarray(lv).reshape(())))
    assert np.isfinite(vals).all()
    assert vals[-1] < vals[0] * 0.98, (vals[0], vals[-1])


def test_transformer_decoder_is_causal():
    """Changing a FUTURE target token must not change earlier decoder
    outputs (inference mode: no dropout noise)."""
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers as L
    from paddle_tpu.models.transformer import (
        TransformerConfig, transformer_decoder, transformer_encoder)

    cfg = TransformerConfig.tiny()
    b, s_src, s_trg = 2, 8, 6
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        src = fluid.data("src", [b, s_src], "int32")
        trg = fluid.data("trg", [b, s_trg], "int32")
        mask = fluid.data("mask", [b, s_src], "float32")
        enc, bias = transformer_encoder(cfg, src, mask, is_test=True)
        dec = transformer_decoder(cfg, trg, enc, bias, is_test=True)
    rng = np.random.RandomState(0)
    src_v = rng.randint(0, 64, (b, s_src)).astype("i4")
    trg_v = rng.randint(0, 64, (b, s_trg)).astype("i4")
    mask_v = np.ones((b, s_src), "f4")
    scope = fluid.executor.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        (d1,) = exe.run(main, feed={"src": src_v, "trg": trg_v,
                                    "mask": mask_v}, fetch_list=[dec])
        trg_v2 = trg_v.copy()
        trg_v2[:, -1] = (trg_v2[:, -1] + 7) % 64  # change the LAST token
        (d2,) = exe.run(main, feed={"src": src_v, "trg": trg_v2,
                                    "mask": mask_v}, fetch_list=[dec])
    d1, d2 = np.asarray(d1), np.asarray(d2)
    np.testing.assert_allclose(d1[:, :-1], d2[:, :-1], rtol=1e-5, atol=1e-6)
    assert not np.allclose(d1[:, -1], d2[:, -1])


def test_transformer_fused_stack_trains_and_is_causal():
    """fuse_stack=True routes through fused_encoder_stack +
    fused_decoder_stack (scan over layers, flash self/cross attention):
    it must train AND keep the decoder causal (future trg tokens cannot
    change earlier positions)."""
    import dataclasses

    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers as L
    from paddle_tpu.models.transformer import (
        TransformerConfig, build_transformer_nmt_program, random_nmt_batch,
        transformer_decoder, transformer_encoder)

    cfg = dataclasses.replace(TransformerConfig.tiny(), fuse_stack=True)
    m, st, feeds, loss = build_transformer_nmt_program(cfg, 4, 16, 12)
    with fluid.program_guard(m, st):
        fluid.optimizer.AdamOptimizer(2e-3).minimize(loss)
    scope = fluid.executor.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(st)
        feed = random_nmt_batch(cfg, 4, 16, 12, seed=0)
        vals = []
        for _ in range(20):
            (lv,) = exe.run(m, feed=feed, fetch_list=[loss])
            vals.append(float(np.asarray(lv).reshape(())))
    assert np.isfinite(vals).all()
    assert vals[-1] < vals[0] * 0.98, (vals[0], vals[-1])

    # causality: decoder outputs at position t must not depend on trg
    # tokens > t (eval mode so dropout is off)
    cfg_t = dataclasses.replace(cfg, dropout=0.0)
    m2, st2 = fluid.Program(), fluid.Program()
    with fluid.program_guard(m2, st2):
        src = L.data("src", [2, 16], dtype="int32", append_batch_size=False)
        trg = L.data("trg", [2, 12], dtype="int32", append_batch_size=False)
        msk = L.data("msk", [2, 16], dtype="float32", append_batch_size=False)
        enc, bias = transformer_encoder(cfg_t, src, msk, is_test=True)
        dec = transformer_decoder(cfg_t, trg, enc, bias, is_test=True)
    rng = np.random.RandomState(0)
    srcv = rng.randint(0, 64, (2, 16)).astype("i4")
    trg_a = rng.randint(0, 64, (2, 12)).astype("i4")
    trg_b = trg_a.copy()
    trg_b[:, 6:] = (trg_b[:, 6:] + 7) % 64  # change only the future
    mskv = np.ones((2, 16), "f4")
    with fluid.scope_guard(fluid.executor.Scope()):
        exe2 = fluid.Executor()
        exe2.run(st2)
        (da,) = exe2.run(m2, feed={"src": srcv, "trg": trg_a, "msk": mskv},
                         fetch_list=[dec])
        (db,) = exe2.run(m2, feed={"src": srcv, "trg": trg_b, "msk": mskv},
                         fetch_list=[dec])
    np.testing.assert_allclose(np.asarray(da)[:, :6], np.asarray(db)[:, :6],
                               rtol=1e-5, atol=1e-5)


def test_label_smooth_loss_analytic_matches_onehot():
    """The analytic smoothed CE == label_smooth(one_hot) + soft-label CE
    (the one-hot path it replaced)."""
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers as L

    b, t, k, eps = 2, 3, 7, 0.1
    rng = np.random.RandomState(1)
    lg = rng.randn(b, t, k).astype("f4") * 3
    lb = rng.randint(0, k, (b, t, 1)).astype("i4")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        logits = fluid.data("lg", [b, t, k], "float32")
        labels = fluid.data("lb", [b, t, 1], "int32")
        ref = L.softmax_with_cross_entropy(
            logits,
            L.label_smooth(L.one_hot(L.reshape(labels, [b, t]), k),
                           epsilon=eps),
            soft_label=True)
        ce_hard = L.softmax_with_cross_entropy(logits, labels)
        mx = L.reduce_max(logits, dim=-1, keep_dim=True)
        lse = L.elementwise_add(
            L.log(L.reduce_sum(L.exp(L.elementwise_sub(logits, mx)),
                               dim=-1, keep_dim=True)), mx)
        uni = L.elementwise_sub(lse, L.reduce_mean(logits, dim=-1,
                                                   keep_dim=True))
        ana = L.elementwise_add(L.scale(ce_hard, scale=1.0 - eps),
                                L.scale(uni, scale=eps))
    with fluid.scope_guard(fluid.executor.Scope()):
        exe = fluid.Executor()
        exe.run(startup)
        r, a = exe.run(main, feed={"lg": lg, "lb": lb},
                       fetch_list=[ref, ana])
    np.testing.assert_allclose(np.asarray(r), np.asarray(a),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.slow  # heavy SP parity; ring/gpipe SP tests cover tier-1
def test_transformer_fused_decoder_sequence_parallel_parity():
    """Fused encoder+decoder stacks under dp2 x sp4 sequence parallelism
    (causal self-attention over the ring, cross-attention k/v gathered by
    GSPMD) must reproduce the single-device loss trajectory."""
    import dataclasses

    import paddle_tpu.fleet as fleet
    import paddle_tpu.fluid as fluid
    from paddle_tpu.models.transformer import (
        TransformerConfig,
        build_transformer_nmt_program,
        random_nmt_batch,
    )

    cfg = dataclasses.replace(
        TransformerConfig.tiny(), fuse_stack=True, dropout=0.0)
    b, s_src, s_trg = 8, 16, 16

    def train(mesh_axes, sp):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 5
        m, st, feeds, loss = build_transformer_nmt_program(
            cfg, b, s_src, s_trg, main_program=main, startup_program=startup)
        scope = fluid.executor.Scope()
        with fluid.scope_guard(scope):
            with fluid.program_guard(m, st):
                strategy = fleet.DistributedStrategy()
                strategy.mesh_axes = mesh_axes
                strategy.sequence_parallel = sp
                fleet.init()
                opt = fleet.distributed_optimizer(
                    fluid.optimizer.AdamOptimizer(1e-2), strategy)
                opt.minimize(loss)
            exe = fluid.Executor()
            exe.run(st)
            out = []
            for i in range(3):
                feed = random_nmt_batch(cfg, b, s_src, s_trg, seed=i)
                (lv,) = exe.run(m, feed=feed, fetch_list=[loss])
                out.append(float(np.asarray(lv).reshape(())))
        return out

    single = train({"dp": 1}, sp=False)
    sp_run = train({"dp": 2, "sp": 4}, sp=True)
    np.testing.assert_allclose(single, sp_run, rtol=5e-5, atol=1e-6)
