# R inference client example (reference r/example/mobilenet.r): drives
# the paddle_tpu C API through dyn.load/.C. PD_RunOnceR follows R's .C
# convention exactly (every argument a pointer, void return); it is the
# .C-shaped face of PD_RunOnce, which tests/test_inference.py validates.
#
#   Rscript mobilenet.R <shim.so> <model_dir> <input_name> <output_name>
args <- commandArgs(trailingOnly = TRUE)
if (length(args) < 4) {
  stop("usage: Rscript mobilenet.R <shim.so> <model_dir> <input> <output>")
}
dyn.load(args[[1]])

x <- runif(4 * 8)
res <- .C("PD_RunOnceR",
          model_dir = as.character(args[[2]]),
          input = as.character(args[[3]]),
          data = as.single(x),
          shape = as.integer(c(4L, 8L)),
          ndim = as.integer(2L),
          output = as.character(args[[4]]),
          out = single(64),
          cap = as.double(64),
          n = double(1))
if (res$n < 0) stop("inference failed (see stderr)")
cat("got", res$n, "elems; head:", head(res$out), "\n")
