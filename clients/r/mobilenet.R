# R inference client example (reference r/example/mobilenet.r): drives
# the paddle_tpu C API's scripting entry PD_RunOnce through dyn.load/.C.
# PD_RunOnce takes int32 shapes precisely so base-R .C can call it
# (R has no int64); the same entry is exercised by
# tests/test_inference.py::test_pd_run_once_scripting_entry via ctypes.
#
#   Rscript mobilenet.R <shim.so> <model_dir> <input_name> <output_name>
args <- commandArgs(trailingOnly = TRUE)
if (length(args) < 4) {
  stop("usage: Rscript mobilenet.R <shim.so> <model_dir> <input> <output>")
}
dyn.load(args[[1]])

x <- runif(4 * 8)
res <- .C("PD_RunOnce",
          as.character(args[[2]]),        # model_dir
          as.character(args[[3]]),        # input name
          as.single(x),                   # data
          as.integer(c(4L, 8L)),          # shape (int32)
          as.integer(2L),                 # ndim
          as.character(args[[4]]),        # output name
          out = single(64),               # output buffer
          as.double(64),                  # capacity (long long via double)
          character(1))                   # err (opaque)
cat("output head:", head(res$out), "\n")
