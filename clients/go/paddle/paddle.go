// Package paddle: Go inference client over the paddle_tpu C API
// (reference go/paddle/{config,predictor,tensor}.go over inference/capi/).
//
// Build: requires cgo and the shim path at runtime:
//
//	CGO_LDFLAGS="-ldl" go build ./...
//	p, err := paddle.NewPredictor(shimPath, modelDir)
//
// NOTE: no Go toolchain exists in the development image, so this file is
// compile-checked only by consumers; it mirrors native/capi_example.c,
// which IS tested (tests/test_inference.py, tests/test_capi_train.py).
package paddle

/*
#cgo LDFLAGS: -ldl
#include <dlfcn.h>
#include <stdlib.h>

typedef void* (*pd_create_fn)(const char*, const char**);
typedef void (*pd_destroy_fn)(void*);
typedef int (*pd_set_in_fn)(void*, const char*, const float*, const long long*, int, const char**);
typedef int (*pd_run_fn)(void*, const char**);
typedef long long (*pd_get_out_fn)(void*, const char*, float*, long long, long long*, int, int*, const char**);

static void* pd_create(void* f, const char* dir, const char** err) {
    return ((pd_create_fn)f)(dir, err);
}
static void pd_destroy(void* f, void* h) { ((pd_destroy_fn)f)(h); }
static int pd_set_in(void* f, void* h, const char* n, const float* d,
                     const long long* s, int nd, const char** err) {
    return ((pd_set_in_fn)f)(h, n, d, s, nd, err);
}
static int pd_run(void* f, void* h, const char** err) {
    return ((pd_run_fn)f)(h, err);
}
static long long pd_get_out(void* f, void* h, const char* n, float* buf,
                            long long cap, long long* shape, int max_ndim,
                            int* ndim, const char** err) {
    return ((pd_get_out_fn)f)(h, n, buf, cap, shape, max_ndim, ndim, err);
}
*/
import "C"

import (
	"errors"
	"unsafe"
)

// Predictor wraps a PD_Predictor handle from the dlopen'd C shim.
type Predictor struct {
	lib     unsafe.Pointer
	handle  unsafe.Pointer
	destroy unsafe.Pointer
	setIn   unsafe.Pointer
	run     unsafe.Pointer
	getOut  unsafe.Pointer
}

func sym(lib unsafe.Pointer, name string) (unsafe.Pointer, error) {
	cn := C.CString(name)
	defer C.free(unsafe.Pointer(cn))
	p := C.dlsym(lib, cn)
	if p == nil {
		return nil, errors.New("missing symbol " + name)
	}
	return p, nil
}

func cerr(err *C.char) error {
	if err == nil {
		return errors.New("unknown C API error")
	}
	msg := C.GoString(err)
	C.free(unsafe.Pointer(err)) // set_err strdup()s; the caller frees
	return errors.New(msg)
}

// NewPredictor dlopens the shim and loads a saved inference model.
func NewPredictor(shimPath, modelDir string) (*Predictor, error) {
	cs := C.CString(shimPath)
	defer C.free(unsafe.Pointer(cs))
	lib := C.dlopen(cs, C.RTLD_NOW|C.RTLD_GLOBAL)
	if lib == nil {
		return nil, errors.New("dlopen failed: " + C.GoString(C.dlerror()))
	}
	fail := func(e error) (*Predictor, error) {
		C.dlclose(lib)
		return nil, e
	}
	create, err := sym(lib, "PD_PredictorCreate")
	if err != nil {
		return fail(err)
	}
	p := &Predictor{lib: lib}
	if p.destroy, err = sym(lib, "PD_PredictorDestroy"); err != nil {
		return fail(err)
	}
	if p.setIn, err = sym(lib, "PD_SetInputFloat"); err != nil {
		return fail(err)
	}
	if p.run, err = sym(lib, "PD_PredictorRun"); err != nil {
		return fail(err)
	}
	if p.getOut, err = sym(lib, "PD_GetOutputFloat"); err != nil {
		return fail(err)
	}
	cd := C.CString(modelDir)
	defer C.free(unsafe.Pointer(cd))
	var msg *C.char
	h := C.pd_create(create, cd, (**C.char)(unsafe.Pointer(&msg)))
	if h == nil {
		return fail(cerr(msg))
	}
	p.handle = h
	return p, nil
}

// SetInputFloat feeds a float32 tensor by name.
func (p *Predictor) SetInputFloat(name string, data []float32, shape []int64) error {
	if len(data) == 0 || len(shape) == 0 {
		return errors.New("SetInputFloat: empty data or shape")
	}
	cn := C.CString(name)
	defer C.free(unsafe.Pointer(cn))
	var msg *C.char
	rc := C.pd_set_in(p.setIn, p.handle, cn,
		(*C.float)(unsafe.Pointer(&data[0])),
		(*C.longlong)(unsafe.Pointer(&shape[0])), C.int(len(shape)),
		(**C.char)(unsafe.Pointer(&msg)))
	if rc != 0 {
		return cerr(msg)
	}
	return nil
}

// Run executes the loaded model.
func (p *Predictor) Run() error {
	var msg *C.char
	if C.pd_run(p.run, p.handle, (**C.char)(unsafe.Pointer(&msg))) != 0 {
		return cerr(msg)
	}
	return nil
}

// GetOutputFloat copies a named float32 output into buf, returning the
// element count and shape.
func (p *Predictor) GetOutputFloat(name string, buf []float32) (int64, []int64, error) {
	cn := C.CString(name)
	defer C.free(unsafe.Pointer(cn))
	var msg *C.char
	var shape [8]C.longlong
	var ndim C.int
	var bufPtr *C.float // nil buf = size-query mode (C API allows NULL)
	if len(buf) > 0 {
		bufPtr = (*C.float)(unsafe.Pointer(&buf[0]))
	}
	n := C.pd_get_out(p.getOut, p.handle, cn,
		bufPtr, C.longlong(len(buf)),
		&shape[0], 8, &ndim, (**C.char)(unsafe.Pointer(&msg)))
	if n < 0 {
		return 0, nil, cerr(msg)
	}
	dims := make([]int64, int(ndim))
	for i := range dims {
		dims[i] = int64(shape[i])
	}
	return int64(n), dims, nil
}

// Destroy releases the predictor and the dlopen'd shim.
func (p *Predictor) Destroy() {
	if p.handle != nil {
		C.pd_destroy(p.destroy, p.handle)
		p.handle = nil
	}
	if p.lib != nil {
		C.dlclose(p.lib)
		p.lib = nil
	}
}
